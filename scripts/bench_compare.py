#!/usr/bin/env python
"""Diff two ``BENCH_*`` JSON artifacts; exit nonzero on regression.

The train bench (``bench.py``) and serve bench (``bench_serve.py``)
each emit ONE JSON line: ``{"metric", "value", "detail": {...}}``.
This comparator turns two of those into a verdict a CI gate can act
on — per-metric deltas, with a configurable relative tolerance —
so "the new round is slower" is a failing exit code, not a thing
someone has to notice while scrolling.

Accepted inputs, per file:

- a bare BENCH JSON object (what ``THEANOMPI_BENCH_SERVE_OUT`` writes),
- a file whose LAST parseable JSON line is the BENCH object (raw
  bench stdout), or
- the driver's wrapper (``BENCH_r{N}.json``: ``{"cmd", "rc", "tail"}``)
  — the BENCH line is recovered from ``tail``.

Compared metrics:

- ``value`` (named by the ``metric`` field) — higher is better.
- ``detail`` latency keys (``*_p50_s``, ``*_p99_s``, ``wall_s``) —
  lower is better.

Only keys present in BOTH files compare; a metric that disappeared is
reported (loudly) but does not fail the gate — schema growth is not a
regression.  A baseline value of 0 (a failed round) skips that metric
with a note, because a ratio against a dead run means nothing.

Exit codes: 0 ok, 1 regression beyond tolerance, 2 usage/parse error.
(Pinned by tests — the tuning driver and CI both script against them.)

``--json`` emits ``{tolerance, rows, notes, regressions, pass}``;
each row carries ``ratio`` (new/baseline) and ``pass`` alongside the
delta so machine consumers (the tuning verdict renderer) never
re-derive the direction logic.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

LOWER_BETTER_SUFFIXES = ("_p50_s", "_p99_s")
LOWER_BETTER_KEYS = ("wall_s",)


def extract_bench(text: str) -> Optional[dict]:
    """The BENCH object from any of the accepted file shapes."""
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if "metric" in doc and "value" in doc:
            return doc
        if "tail" in doc:  # driver wrapper: recover from captured stdout
            text = str(doc.get("tail", ""))
        else:
            return None
    # scan lines bottom-up: the BENCH line is the run's last word
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "metric" in cand and "value" in cand:
            return cand
    return None


def comparable_metrics(doc: dict) -> Dict[str, Tuple[float, str]]:
    """``name -> (value, direction)`` with direction 'higher'/'lower'."""
    out: Dict[str, Tuple[float, str]] = {
        str(doc.get("metric", "value")): (float(doc["value"]), "higher")
    }
    detail = doc.get("detail") or {}
    for key, val in detail.items():
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        if key.endswith(LOWER_BETTER_SUFFIXES) or key in LOWER_BETTER_KEYS:
            out[key] = (float(val), "lower")
    return out


def compare(
    base: dict, new: dict, tolerance: float
) -> Tuple[List[dict], List[str]]:
    """``(rows, notes)``; a row is one metric's verdict."""
    b = comparable_metrics(base)
    n = comparable_metrics(new)
    rows: List[dict] = []
    notes: List[str] = []
    for key in sorted(set(b) | set(n)):
        if key not in n:
            notes.append(f"{key}: present in baseline only (dropped?)")
            continue
        if key not in b:
            notes.append(f"{key}: new metric (no baseline)")
            continue
        old_v, direction = b[key]
        new_v, _ = n[key]
        if old_v == 0:
            notes.append(
                f"{key}: baseline is 0 (failed round?) — skipped"
            )
            continue
        delta = (new_v - old_v) / abs(old_v)
        worse = -delta if direction == "higher" else delta
        regression = worse > tolerance
        rows.append(
            {
                "metric": key,
                "direction": direction,
                "baseline": old_v,
                "new": new_v,
                "ratio": new_v / old_v,
                "delta_pct": 100.0 * delta,
                "regression": regression,
                "pass": not regression,
            }
        )
    return rows, notes


def render(rows: List[dict], notes: List[str], tolerance: float) -> str:
    lines = [
        f"{'metric':<40} {'baseline':>12} {'new':>12} {'delta':>8}  verdict"
    ]
    lines.append("-" * len(lines[0]))
    for r in rows:
        verdict = (
            f"REGRESSION (>{tolerance * 100:.0f}% worse)"
            if r["regression"]
            else "ok"
        )
        lines.append(
            f"{r['metric']:<40} {r['baseline']:>12.4f} {r['new']:>12.4f} "
            f"{r['delta_pct']:>+7.1f}%  {verdict}"
        )
    for note in notes:
        lines.append(f"note: {note}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="diff two BENCH_* JSON files; exit 1 on regression"
    )
    p.add_argument("baseline", help="older BENCH json (the reference)")
    p.add_argument("candidate", help="newer BENCH json (under test)")
    p.add_argument(
        "--tolerance",
        type=float,
        default=0.05,
        help="relative worsening allowed before failing (default 0.05)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    args = p.parse_args(argv)

    docs = []
    for path in (args.baseline, args.candidate):
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
        doc = extract_bench(text)
        if doc is None:
            print(
                f"{path}: no BENCH JSON object found (need a line with "
                "'metric' and 'value')",
                file=sys.stderr,
            )
            return 2
        docs.append(doc)
    base, new = docs
    if base.get("metric") != new.get("metric"):
        print(
            f"warning: comparing different benches "
            f"({base.get('metric')} vs {new.get('metric')}) — only "
            "shared detail keys align",
            file=sys.stderr,
        )
    rows, notes = compare(base, new, args.tolerance)
    regressions = [r for r in rows if r["regression"]]
    if args.json:
        print(
            json.dumps(
                {
                    "tolerance": args.tolerance,
                    "rows": rows,
                    "notes": notes,
                    "regressions": [r["metric"] for r in regressions],
                    "pass": not regressions,
                },
                indent=2,
            )
        )
    else:
        sys.stdout.write(render(rows, notes, args.tolerance))
    for r in regressions:
        print(
            f"REGRESSION: {r['metric']} {r['delta_pct']:+.1f}% "
            f"({'drop' if r['direction'] == 'higher' else 'rise'} beyond "
            f"{args.tolerance * 100:.0f}% tolerance)",
            file=sys.stderr,
        )
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())

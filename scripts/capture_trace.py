#!/usr/bin/env python
"""Capture a jax.profiler trace of the flagship AlexNet BSP step.

Usage: python scripts/capture_trace.py [outdir] [config_overrides_json]

The Perfetto half of the dump (``*.trace.json.gz``) is plain JSON —
``scripts/analyze_trace.py`` aggregates it into a per-op time table so
the hot spots are readable without TensorBoard.

DEFAULTS TO THE FAKE-CPU MESH: ``jax.profiler.trace`` against the axon
TPU tunnel hung and wedged it in r4 (docs/perf/NOTES.md). Set
``THEANOMPI_ALLOW_AXON_TRACE=1`` only if that backend bug is known
fixed; otherwise op-level TPU analysis comes from the committed
``docs/perf/trace_r2``.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("THEANOMPI_ALLOW_AXON_TRACE") != "1":
    from theanompi_tpu.cachedir import cpu_xla_flags

    os.environ["XLA_FLAGS"] = cpu_xla_flags(os.environ.get("XLA_FLAGS", ""))

import jax

if os.environ.get("THEANOMPI_ALLOW_AXON_TRACE") != "1":
    # config API, not env: axon's sitecustomize pre-imports jax, so
    # JAX_PLATFORMS alone is ignored (verify SKILL.md gotcha)
    jax.config.update("jax_platforms", "cpu")

from theanompi_tpu.models.alex_net import AlexNet
from theanompi_tpu.runtime.mesh import make_mesh, shard_batch


def main():
    on_cpu = os.environ.get("THEANOMPI_ALLOW_AXON_TRACE") != "1"
    # CPU smokes must not land in docs/perf/ next to real-chip traces
    outdir = sys.argv[1] if len(sys.argv) > 1 else (
        "/tmp/trace_cpu_smoke" if on_cpu else "docs/perf/trace_r4")
    overrides = json.loads(sys.argv[2]) if len(sys.argv) > 2 else {}
    mesh = make_mesh()
    cfg = dict(
        # full-size AlexNet steps take ~30s EACH on the 1-core CPU
        # fallback — shrink there so the smoke path finishes
        batch_size=64 if on_cpu else 512,
        compute_dtype="bfloat16",
        lr=1e-3,
        n_synth_batches=2 if on_cpu else 8,
        print_freq=10_000,
    )
    cfg.update(overrides)  # update, not **: overrides may replace defaults
    model = AlexNet(config=cfg, mesh=mesh)
    n_warm, n_trace = (2, 3) if on_cpu else (10, 20)
    train_fn = model.compile_train()
    batches = [shard_batch(mesh, b) for b in model.data.train_batches()]
    p, s, o = model.params, model.net_state, model.opt_state
    keys = list(jax.random.split(jax.random.PRNGKey(0), 64))

    def step(p, s, o, i):
        x, y = batches[i % len(batches)]
        return train_fn(p, s, o, x, y, keys[i % len(keys)])

    for i in range(n_warm):  # compile + steady-state warmup outside the trace
        p, s, o, loss, err = step(p, s, o, i)
    jax.block_until_ready(loss)

    os.makedirs(outdir, exist_ok=True)
    with jax.profiler.trace(outdir):
        t0 = time.perf_counter()
        for i in range(n_trace):
            p, s, o, loss, err = step(p, s, o, i)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
    print(f"traced {n_trace} steps in {dt:.3f}s -> {dt / n_trace * 1e3:.2f} "
          f"ms/step ({n_trace * model.global_batch / dt:.0f} img/s) -> {outdir}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Serving benchmark — prints ONE ``BENCH_serve`` JSON line.

The training bench (bench.py) answers "how fast does a step train";
this answers the serving-side questions: sustained generated tokens/s
through the continuous-batching scheduler, and request latency (TTFT /
TPOT, p50/p99) under a synthetic open-loop Poisson arrival process —
the standard serving-bench shape (requests arrive on their own clock;
a backed-up server cannot slow the arrivals down).

Protocol:
- ``TransformerLM`` at the flagship serve config (rehearsal shrinks it,
  same code path — the bench.py CPU-rehearsal discipline, VERDICT r3
  #2), fresh-initialized params (throughput does not depend on weight
  values; loader round-trips are covered by tests/test_serving.py).
- Arrivals: exponential inter-arrival gaps at ``arrival_rate_rps``,
  prompt lengths uniform over the engine's bucket range, fixed
  ``max_new_tokens``.
- Drive loop: submit every request whose arrival time has passed, then
  one scheduler tick; repeat until drained.  Wall-clock is real (the
  engine really runs); arrival times are pre-drawn from a seeded RNG so
  two runs see the same workload.

Env: ``THEANOMPI_BENCH_CPU=1`` = CPU rehearsal (fake 8-device mesh,
shrunk sizes); ``THEANOMPI_BENCH_SERVE_OUT`` = also write the JSON to a
file (default: print only).  bench.py delegates here when
``THEANOMPI_BENCH_SERVE=1`` so the driver's one entry point covers both
benches.
"""

import json
import os
import sys
import time

CPU_REHEARSAL = os.environ.get("THEANOMPI_BENCH_CPU") == "1"
if CPU_REHEARSAL:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from theanompi_tpu.cachedir import cpu_xla_flags

    os.environ["XLA_FLAGS"] = cpu_xla_flags(os.environ.get("XLA_FLAGS", ""))

import jax

if CPU_REHEARSAL:
    # the axon sitecustomize pre-imports jax; pin through the config API
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def emit(value: float, detail: dict, measured_now: bool) -> None:
    """THE one JSON line — same schema discipline as bench.py."""
    line = json.dumps(
        {
            "metric": "transformer_serve_tokens_per_sec",
            "value": round(value, 2),
            "unit": "generated tokens/sec",
            "vs_baseline": 1.0,
            "measured_now": measured_now,
            "detail": detail,
        }
    )
    print(line)
    out = os.environ.get("THEANOMPI_BENCH_SERVE_OUT")
    if out:
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, out)


# every size that differs between the real bench and the CPU rehearsal
_KNOBS_REAL = dict(
    d_model=512, n_heads=8, n_layers=8, vocab_size=4096, seq_len=1024,
    n_slots=8, max_len=1024, n_requests=64, arrival_rate_rps=16.0,
    max_new_tokens=32, prompt_lo=16, prompt_hi=256,
)
_KNOBS_REHEARSAL = dict(
    d_model=32, n_heads=4, n_layers=2, vocab_size=64, seq_len=64,
    n_slots=2, max_len=64, n_requests=6, arrival_rate_rps=50.0,
    max_new_tokens=4, prompt_lo=2, prompt_hi=8,
)


def main():
    import numpy as np

    knobs = _KNOBS_REHEARSAL if CPU_REHEARSAL else _KNOBS_REAL
    # same attribution contract as bench.py: the BENCH_serve line
    # carries trace-export paths + a metrics snapshot (TTFT/TPOT
    # histograms, slot/queue gauges, prefill-bucket counters)
    from theanompi_tpu import observability as observability

    observability.enable_tracing()
    if not CPU_REHEARSAL and jax.default_backend() not in ("tpu",):
        # same guard shape as bench.py: a dead tunnel silently falling
        # back to 1 CPU device must not masquerade as a TPU number
        emit(0.0, {"error": f"backend is {jax.default_backend()!r}, not "
                   "tpu — set THEANOMPI_BENCH_CPU=1 for the rehearsal"},
             measured_now=False)
        sys.exit(1)

    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.runtime.recorder import Recorder
    from theanompi_tpu.serving import (
        ContinuousBatchingScheduler, Request, ServingEngine, ServingMetrics,
    )

    cfg = dict(
        seq_len=knobs["seq_len"], vocab_size=knobs["vocab_size"],
        d_model=knobs["d_model"], n_heads=knobs["n_heads"],
        n_layers=knobs["n_layers"], batch_size=1, n_synth_train=2,
        n_synth_val=1, comm_probe=False, print_freq=10_000,
    )
    model = TransformerLM(config=cfg)
    engine = ServingEngine(
        model, n_slots=knobs["n_slots"], max_len=knobs["max_len"]
    )
    rec = Recorder(verbose=False)
    metrics = ServingMetrics(recorder=rec)
    sched = ContinuousBatchingScheduler(engine, metrics=metrics)

    # seeded open-loop Poisson workload, pre-drawn
    rng = np.random.RandomState(0)
    n = knobs["n_requests"]
    arrivals = np.cumsum(rng.exponential(
        1.0 / knobs["arrival_rate_rps"], size=n
    ))
    prompts = [
        rng.randint(0, knobs["vocab_size"],
                    size=rng.randint(knobs["prompt_lo"],
                                     knobs["prompt_hi"] + 1)).tolist()
        for _ in range(n)
    ]

    # warm the compiles OUTSIDE the measured window (one prefill bucket
    # per distinct bucket + the decode step), mirroring bench.py's
    # warmup-exclusion protocol
    warm = ContinuousBatchingScheduler(engine, metrics=None)
    warm.submit(Request(id="warm", prompt=prompts[0],
                        max_new_tokens=min(2, knobs["max_new_tokens"])))
    warm.run()

    t0 = time.perf_counter()
    submitted = 0
    while submitted < n or sched.queue or sched.n_active:
        now = time.perf_counter() - t0
        while submitted < n and arrivals[submitted] <= now:
            sched.submit(Request(
                id=f"req{submitted}", prompt=prompts[submitted],
                max_new_tokens=knobs["max_new_tokens"],
            ))
            submitted += 1
        if sched.queue or sched.n_active:
            sched.step()
        elif submitted < n:
            time.sleep(min(0.005, max(0.0, arrivals[submitted] - now)))
    dt = time.perf_counter() - t0

    summary = metrics.summary()
    n_tokens = summary["n_tokens_out"]
    detail = {
        "chips": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind,
        "model": {k: knobs[k] for k in
                  ("d_model", "n_heads", "n_layers", "vocab_size")},
        "n_slots": knobs["n_slots"],
        "max_len": knobs["max_len"],
        "buckets": list(engine.buckets),
        "workload": {
            "n_requests": n,
            "arrival_rate_rps": knobs["arrival_rate_rps"],
            "prompt_len_range": [knobs["prompt_lo"], knobs["prompt_hi"]],
            "max_new_tokens": knobs["max_new_tokens"],
            "distribution": "poisson(open-loop), seeded",
        },
        "wall_s": round(dt, 3),
        "ttft_p50_s": round(summary["ttft_p50_s"], 4),
        "ttft_p99_s": round(summary["ttft_p99_s"], 4),
        "tpot_p50_s": round(summary["tpot_p50_s"], 4),
        "tpot_p99_s": round(summary["tpot_p99_s"], 4),
        # which estimator produced each percentile pair: "exact"
        # nearest-rank over the per-request rows, or "histogram"
        # bucket interpolation once the row window overflowed — a
        # JSON consumer must never mistake one for the other
        "percentile_estimators": summary["estimators"],
        "cpu_rehearsal": CPU_REHEARSAL,
    }
    try:
        paths = observability.dump_all(prefix="bench_serve_")
        detail["observability"] = {
            "trace_chrome": paths["trace_chrome"],
            "trace_raw": paths["trace_raw"],
            "metrics": observability.get_registry().snapshot(),
        }
        if "doctor" in paths:
            detail["observability"]["doctor"] = paths["doctor"]
    except OSError as e:  # export must never discard the measurement
        print(f"[bench_serve] observability export failed: {e}",
              file=sys.stderr, flush=True)
        detail["observability"] = f"failed: {type(e).__name__}: {e}"
    emit(n_tokens / dt, detail, measured_now=True)


if __name__ == "__main__":
    main()

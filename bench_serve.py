#!/usr/bin/env python
"""Serving benchmark — prints ONE ``BENCH_serve`` JSON line.

The training bench (bench.py) answers "how fast does a step train";
this answers the serving-side questions: sustained generated tokens/s
through the continuous-batching scheduler, request latency (TTFT /
TPOT, p50/p99) under a synthetic open-loop Poisson arrival process —
the standard serving-bench shape (requests arrive on their own clock;
a backed-up server cannot slow the arrivals down) — and, since the
paged KV cache landed, two capacity questions the contiguous engine
could not even pose:

- **long-tail concurrency** — at EQUAL cache memory, how many
  sequences can each engine hold simultaneously under a mixed-length
  (mostly-short, occasionally-huge) burst?  The contiguous engine
  reserves ``max_len`` rows per slot, so its answer is its slot
  count; the paged engine allocates blocks for what a sequence can
  actually need.  ``detail.paged.long_tail.concurrency_ratio`` is the
  measured paged/contiguous peak-concurrency ratio (the perf-gate
  serve leg requires >= 2).
- **prefix reuse** — a shared system prompt is prefilled once and its
  immutable blocks refcounted across requests.
  ``detail.paged.prefix`` records the measured hit rate and the
  prefilled-token count with reuse vs. the no-reuse baseline (the
  gate requires hit_rate > 0 and fewer prefilled tokens).
- **speculative decoding** (``detail.spec``) — generated tokens/s with
  a truncated self-draft proposing k tokens per round vs. the plain
  tick, over the same seeded burst: acceptance rate, draft/verify
  dispatch counts, speedup, and a token-identity bit (greedy spec MUST
  equal greedy plain — the perf-gate serve leg fails otherwise).  The
  probe model is a **distilled-draft proxy**: residual blocks damped
  and the shared embedding signal boosted so the 1-layer draft tracks
  the full target the way a trained draft tracks its teacher — the
  FLOPs per dispatch are unchanged, so the tokens/s ratio is a real
  measurement of the machinery at the reported acceptance rate.
- **int8 KV blocks** (``detail.kv_quant``) — blocks-per-chip at equal
  cache bytes for kv_dtype='int8' vs 'fp32' (the >= 2x capacity
  criterion) and a greedy-drift probe (fraction of greedy tokens that
  differ across the quantized cache — the gate bounds it).
- **the serving fleet** (``detail.fleet``, ``--replicas N`` /
  ``THEANOMPI_BENCH_SERVE_REPLICAS``) — N replicas behind the
  ``serving/fleet.py`` router: prefix-affinity routing vs round-robin
  on a multi-tenant shared-prefix workload (per-replica tokens/s,
  affinity hit-rate, reused vs prefilled tokens), the radix-vs-chain
  prefix cache comparison under pool pressure (radix hit-rate must
  beat chain with strictly fewer prefilled tokens — outputs pinned
  identical), a kill-one-replica failover probe (re-admissions,
  token-identity vs the uninterrupted fleet) and a health-shed probe
  (zero admissions while red).
- **the online learning loop** (``detail.publish``) — an in-process
  EASGD core publishes a fresh center mid-decode and the replica's
  ``publish.WeightSubscriber`` pulls/validates/installs it between
  ticks: install wait behind in-flight work, snapshot bytes pulled,
  and the extra-compile count (must be 0 — params are data).  Token
  identity / rollback / refusal correctness lives in the PUBLISH chaos
  drill (perf_gate publish leg), not here.
- **request forensics** (``detail.request_forensics``) — per-request
  tail tracing is enabled around the measured open-loop window
  (``forensics_threshold_s`` knob; requests slower than it are
  retained whole) and the request doctor's phase breakdown of the
  single slowest request rides the JSON line: queue / prefill /
  decode / backpressure attribution with a coverage fraction the
  perf-gate FORENSICS leg requires >= 0.9, plus retained/recycled
  counts (a green run must recycle ~everything).

Protocol:
- ``TransformerLM`` at the flagship serve config (rehearsal shrinks it,
  same code path — the bench.py CPU-rehearsal discipline, VERDICT r3
  #2), fresh-initialized params (throughput does not depend on weight
  values; loader round-trips are covered by tests/test_serving.py).
- Headline workload: exponential inter-arrival gaps at
  ``arrival_rate_rps``, prompt lengths uniform over the engine's
  bucket range, fixed ``max_new_tokens`` — driven through the PAGED
  engine (``THEANOMPI_BENCH_SERVE_ENGINE=contiguous`` to flip back).
- Long-tail workload knob: ``long_tail_frac_long`` controls the
  fraction of near-``max_len`` prompts in the burst (default 0.25 —
  raise it to stress block churn, lower it to stress lane count).
- Drive loop: submit every request whose arrival time has passed, then
  one scheduler tick; repeat until drained.  Wall-clock is real (the
  engine really runs); arrival times are pre-drawn from a seeded RNG so
  two runs see the same workload.

Env: ``THEANOMPI_BENCH_CPU=1`` = CPU rehearsal (fake 8-device mesh,
shrunk sizes); ``THEANOMPI_BENCH_SERVE_OUT`` = also write the JSON to a
file (default: print only).  bench.py delegates here when
``THEANOMPI_BENCH_SERVE=1`` so the driver's one entry point covers both
benches.
"""

import json
import os
import sys
import time

CPU_REHEARSAL = os.environ.get("THEANOMPI_BENCH_CPU") == "1"
if CPU_REHEARSAL:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from theanompi_tpu.cachedir import cpu_xla_flags

    os.environ["XLA_FLAGS"] = cpu_xla_flags(os.environ.get("XLA_FLAGS", ""))

import jax

if CPU_REHEARSAL:
    # the axon sitecustomize pre-imports jax; pin through the config API
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def emit(value: float, detail: dict, measured_now: bool) -> None:
    """THE one JSON line — same schema discipline as bench.py."""
    line = json.dumps(
        {
            "metric": "transformer_serve_tokens_per_sec",
            "value": round(value, 2),
            "unit": "generated tokens/sec",
            "vs_baseline": 1.0,
            "measured_now": measured_now,
            "detail": detail,
        }
    )
    print(line)
    out = os.environ.get("THEANOMPI_BENCH_SERVE_OUT")
    if out:
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, out)


# every size that differs between the real bench and the CPU rehearsal
_KNOBS_REAL = dict(
    d_model=512, n_heads=8, n_layers=8, vocab_size=4096, seq_len=1024,
    n_slots=8, max_len=1024, n_requests=64, arrival_rate_rps=16.0,
    max_new_tokens=32, prompt_lo=16, prompt_hi=256,
    # paged geometry: lanes beyond the contiguous slot count are the
    # point — memory is bounded by blocks, not lanes
    block_size=32, paged_slots=32, prefill_chunk=256,
    # long-tail burst: mixed lengths at equal cache memory
    long_tail_requests=48, long_tail_new_tokens=8, long_tail_frac_long=0.25,
    # shared-system-prompt workload
    prefix_requests=16, prefix_len=128, prefix_tail=16,
    prefix_new_tokens=8,
    # speculative-decoding probe: its own (bigger) model so draft vs
    # target cost separates from dispatch overhead; distilled-draft
    # proxy params (see module docstring)
    spec_d_model=512, spec_n_heads=8, spec_n_layers=12, spec_vocab=1024,
    spec_seq_len=256, spec_slots=8, spec_block=16, spec_chunk=64,
    spec_k=8, spec_draft_layers=1, spec_requests=8, spec_new_tokens=48,
    spec_prompt_lo=4, spec_prompt_hi=16, spec_damp=0.003,
    spec_emb_boost=10.0,
    # int8-KV capacity + drift probe
    kvq_prompts=4, kvq_new_tokens=16,
    # serving-fleet probe: replicas × multi-tenant shared prefixes
    fleet_replicas=3, fleet_prefixes=3, fleet_requests_per_prefix=4,
    fleet_prefix_len=64, fleet_tail=8, fleet_new_tokens=8,
    fleet_slots=4, fleet_evict_after_s=2.0,
    fleet_failover_requests=4, fleet_failover_new_tokens=24,
    # request forensics: retain whole traces only past this latency
    # (30s = nothing on a green run; the worst-latency ring still
    # feeds the doctor's slowest-request breakdown)
    forensics_threshold_s=30.0,
)
_KNOBS_REHEARSAL = dict(
    d_model=32, n_heads=4, n_layers=2, vocab_size=64, seq_len=64,
    n_slots=2, max_len=64, n_requests=6, arrival_rate_rps=50.0,
    max_new_tokens=4, prompt_lo=2, prompt_hi=8,
    block_size=8, paged_slots=8, prefill_chunk=16,
    long_tail_requests=12, long_tail_new_tokens=2, long_tail_frac_long=0.25,
    prefix_requests=6, prefix_len=24, prefix_tail=4,
    prefix_new_tokens=2,
    # the spec probe keeps a compute-dominated shape even in rehearsal:
    # at toy sizes every dispatch is overhead-bound and NO spec scheme
    # can win (the draft tick costs the same as the target tick), so the
    # rehearsal would measure the dispatcher, not the machinery
    spec_d_model=256, spec_n_heads=8, spec_n_layers=12, spec_vocab=512,
    spec_seq_len=128, spec_slots=8, spec_block=16, spec_chunk=32,
    spec_k=8, spec_draft_layers=1, spec_requests=8, spec_new_tokens=48,
    spec_prompt_lo=4, spec_prompt_hi=16, spec_damp=0.003,
    spec_emb_boost=10.0,
    kvq_prompts=4, kvq_new_tokens=8,
    fleet_replicas=3, fleet_prefixes=3, fleet_requests_per_prefix=4,
    fleet_prefix_len=24, fleet_tail=4, fleet_new_tokens=4,
    fleet_slots=2, fleet_evict_after_s=2.0,
    fleet_failover_requests=4, fleet_failover_new_tokens=16,
    forensics_threshold_s=30.0,
)

# ---- closed-loop tuning contract (theanompi_tpu/tuning/trials.py) ---------
# The trial harness injects one candidate config via THEANOMPI_TUNE_
# OVERRIDES (JSON knob->value) and a workload seed via THEANOMPI_BENCH_
# SEED; the bench applies what it understands, echoes the full map in
# detail.tuning, and exits loudly on a knob it does not know.  All
# seeded workload streams shift together with the trial seed; seed 0
# reproduces the historical workloads bit-for-bit.
TUNE_SEED = int(os.environ.get("THEANOMPI_BENCH_SEED", "0") or 0)
_SEED_BASE = TUNE_SEED * 1000


def _tune_overrides():
    raw = os.environ.get("THEANOMPI_TUNE_OVERRIDES", "")
    if not raw.strip():
        return None
    try:
        overrides = json.loads(raw)
    except ValueError as e:
        print(f"[bench_serve] bad THEANOMPI_TUNE_OVERRIDES json: {e}",
              file=sys.stderr)
        sys.exit(2)
    if not isinstance(overrides, dict):
        print("[bench_serve] THEANOMPI_TUNE_OVERRIDES must be a JSON "
              "object", file=sys.stderr)
        sys.exit(2)
    return overrides


def _drive_open_loop(sched, Request, prompts, arrivals, max_new):
    """The open-loop Poisson drive: submit what has arrived, tick."""
    t0 = time.perf_counter()
    n = len(prompts)
    submitted = 0
    while submitted < n or sched.queue or sched.n_active:
        now = time.perf_counter() - t0
        while submitted < n and arrivals[submitted] <= now:
            sched.submit(Request(
                id=f"req{submitted}", prompt=prompts[submitted],
                max_new_tokens=max_new,
            ))
            submitted += 1
        if sched.queue or sched.n_active:
            sched.step()
        elif submitted < n:
            time.sleep(min(0.005, max(0.0, arrivals[submitted] - now)))
    return time.perf_counter() - t0


def _drive_burst(sched, Request, prompts, max_new, tag):
    """Everything arrives at t=0 — the concurrency probe."""
    for j, p in enumerate(prompts):
        sched.submit(Request(id=f"{tag}{j}", prompt=list(p),
                             max_new_tokens=max_new))
    sched.run()
    return sched.stats


def _shape_spec_params(params, n_layers, damp, emb_boost):
    """Distilled-draft proxy weights: boost the (shared) embedding
    signal and damp every block's residual contribution, so the
    truncated self-draft's argmax tracks the target's the way a trained
    draft tracks its teacher.  FLOPs per dispatch are UNCHANGED — only
    the agreement statistics move, and the bench reports the measured
    acceptance rate next to the speedup it produced."""
    p = list(params)
    emb = dict(p[0])
    emb["table"] = emb["table"] * emb_boost
    p[0] = emb
    for i in range(2, 2 + n_layers):
        bp = dict(p[i])
        attn = dict(bp["attn"])
        mo = dict(bp["mlp_out"])
        attn["wo"] = attn["wo"] * damp
        mo["w"] = mo["w"] * damp
        mo["b"] = mo["b"] * damp
        bp["attn"] = attn
        bp["mlp_out"] = mo
        p[i] = bp
    return p


def _spec_probe(knobs):
    """detail.spec: tokens/s through the SAME engine with speculation
    off vs on (k-token truncated self-draft), same seeded burst."""
    import numpy as np

    from theanompi_tpu.models.transformer import TransformerLM, make_draft
    from theanompi_tpu.serving import PagedServingEngine
    from theanompi_tpu.serving.scheduler import (
        ContinuousBatchingScheduler, Request,
    )

    cfg = dict(
        seq_len=knobs["spec_seq_len"], vocab_size=knobs["spec_vocab"],
        d_model=knobs["spec_d_model"], n_heads=knobs["spec_n_heads"],
        n_layers=knobs["spec_n_layers"], batch_size=1, n_synth_train=2,
        n_synth_val=1, comm_probe=False, print_freq=10_000,
    )
    model = TransformerLM(config=cfg)
    model.params = _shape_spec_params(
        model.params, knobs["spec_n_layers"], knobs["spec_damp"],
        knobs["spec_emb_boost"],
    )
    geom = dict(
        n_slots=knobs["spec_slots"], max_len=knobs["spec_seq_len"],
        block_size=knobs["spec_block"], prefill_chunk=knobs["spec_chunk"],
    )
    engine = PagedServingEngine(model, **geom)
    draft = make_draft(model, n_layers=knobs["spec_draft_layers"])
    draft_engine = PagedServingEngine(draft, **geom)

    rng = np.random.RandomState(_SEED_BASE + 2)
    prompts = [
        rng.randint(
            0, knobs["spec_vocab"],
            size=rng.randint(knobs["spec_prompt_lo"],
                             knobs["spec_prompt_hi"] + 1),
        ).tolist()
        for _ in range(knobs["spec_requests"])
    ]

    def drive(spec_on):
        kw = (
            dict(spec_k=knobs["spec_k"], draft_engine=draft_engine)
            if spec_on else {}
        )
        sched = ContinuousBatchingScheduler(engine, **kw)
        for j, p in enumerate(prompts):
            sched.submit(Request(id=f"sp{j}", prompt=list(p),
                                 max_new_tokens=knobs["spec_new_tokens"]))
        t0 = time.perf_counter()
        out = sched.run()
        return out, time.perf_counter() - t0, sched

    drive(False)  # warm both programs outside the measured window
    drive(True)
    out_off, dt_off, _ = drive(False)
    out_on, dt_on, sched_on = drive(True)
    n_tokens = sum(len(v) for v in out_off.values())
    s = sched_on.spec_summary()
    tps_off = n_tokens / dt_off
    tps_on = n_tokens / dt_on
    return {
        "model": {k: knobs[f"spec_{k2}"] for k, k2 in
                  (("d_model", "d_model"), ("n_heads", "n_heads"),
                   ("n_layers", "n_layers"), ("vocab_size", "vocab"))},
        "draft_layers": knobs["spec_draft_layers"],
        "k": knobs["spec_k"],
        "n_requests": knobs["spec_requests"],
        "max_new_tokens": knobs["spec_new_tokens"],
        "damp": knobs["spec_damp"],
        "emb_boost": knobs["spec_emb_boost"],
        "token_identical": out_on == out_off,
        "tokens_per_sec_spec_off": round(tps_off, 2),
        "tokens_per_sec_spec_on": round(tps_on, 2),
        "speedup": round(tps_on / tps_off, 3),
        "accept_rate": s["accept_rate"],
        "tokens_per_round": s["tokens_per_round"],
        "rounds": s["rounds"],
        "draft_dispatches": s["draft_dispatches"],
        "verify_dispatches": s["verify_dispatches"],
        "proposed": s["proposed"],
        "accepted": s["accepted"],
    }


def _kv_quant_probe(model, engine, knobs, prompts):
    """detail.kv_quant: blocks per chip at EQUAL cache bytes for int8
    vs fp32 pools (the >= 2x capacity criterion), plus the greedy-drift
    probe over real workload prompts."""
    from theanompi_tpu.serving import PagedServingEngine

    i8 = PagedServingEngine(
        model, n_slots=knobs["paged_slots"], max_len=knobs["max_len"],
        block_size=knobs["block_size"], prefill_chunk=knobs["prefill_chunk"],
        kv_dtype="int8",
    )
    budget = (engine.n_blocks) * engine.kv_block_bytes()
    blocks_fp32 = engine.blocks_at_budget(budget)
    blocks_int8 = i8.blocks_at_budget(budget)
    agree = total = 0
    for p in prompts[: knobs["kvq_prompts"]]:
        a = engine.greedy(list(p), knobs["kvq_new_tokens"])
        b = i8.greedy(list(p), knobs["kvq_new_tokens"])
        agree += sum(x == y for x, y in zip(a, b))
        total += len(a)
    return {
        "kv_block_bytes_fp32": engine.kv_block_bytes(),
        "kv_block_bytes_int8": i8.kv_block_bytes(),
        "equal_bytes_budget": budget,
        "pool_blocks_fp32": blocks_fp32,
        "pool_blocks_int8": blocks_int8,
        "blocks_per_chip_ratio": round(blocks_int8 / blocks_fp32, 3),
        "drift_probe_tokens": total,
        "greedy_agree_tokens": agree,
        "greedy_drift": round(1.0 - agree / max(1, total), 4),
    }


def _fleet_probe(model, knobs, n_replicas):
    """detail.fleet: the multi-replica front door measured four ways —
    affinity-vs-round-robin routing, radix-vs-chain caching under pool
    pressure, kill-one-replica failover, and health shedding.  All
    in-process (the same protocol a TCP replica serves); wall-clock is
    real."""
    import numpy as np

    from theanompi_tpu.serving import (
        ContinuousBatchingScheduler, PagedServingEngine, Request,
    )
    from theanompi_tpu.serving.fleet import FleetRouter, ServeReplica

    bs = knobs["block_size"]
    geom = dict(
        n_slots=knobs["fleet_slots"], max_len=knobs["max_len"],
        block_size=bs, prefill_chunk=knobs["prefill_chunk"],
    )
    engines = [PagedServingEngine(model, **geom) for _ in range(n_replicas)]
    rng = np.random.RandomState(_SEED_BASE + 4)
    vocab = knobs["vocab_size"]
    prefixes = [
        rng.randint(0, vocab, size=knobs["fleet_prefix_len"]).tolist()
        for _ in range(knobs["fleet_prefixes"])
    ]
    tails = [
        rng.randint(0, vocab, size=knobs["fleet_tail"]).tolist()
        for _ in range(
            knobs["fleet_prefixes"] * knobs["fleet_requests_per_prefix"]
        )
    ]
    new = knobs["fleet_new_tokens"]

    def build(affinity=True, n=None):
        reps = [
            ServeReplica(f"b{i}", engines[i]).start()
            for i in range(n or n_replicas)
        ]
        router = FleetRouter(
            evict_after_s=knobs["fleet_evict_after_s"], affinity=affinity,
        )
        for rep in reps:
            router.add_replica(rep.name, rep)
        return reps, router

    def drain(reps):
        deadline = time.perf_counter() + 600
        while not all(r.scheduler.idle for r in reps):
            if time.perf_counter() > deadline:
                raise RuntimeError("fleet probe replicas never drained")
            time.sleep(0.005)

    def warm():
        reps, router = build()
        for i, rep in enumerate(reps):
            router.submit(Request(
                id=f"w{i}", prompt=prefixes[0][: bs + 1],
                max_new_tokens=2,
            ))
        router.run(timeout_s=600)
        for rep in reps:
            rep.stop()

    def routing_arm(affinity):
        reps, router = build(affinity=affinity)
        # tenant warmup wave: one request per prefix, run to completion
        # so caches are resident and summaries gossiped before the
        # measured wave (affinity can only follow blocks that exist)
        rid = 0
        for p in prefixes:
            router.submit(Request(id=f"f{rid}", prompt=list(p) + tails[rid],
                                  max_new_tokens=new))
            rid += 1
        router.run(timeout_s=600)
        t0 = time.perf_counter()
        n_tokens = 0
        for wave in range(knobs["fleet_requests_per_prefix"] - 1):
            for p in prefixes:
                router.submit(Request(
                    id=f"f{rid}", prompt=list(p) + tails[rid],
                    max_new_tokens=new,
                ))
                rid += 1
            router.run(timeout_s=600)
        dt = time.perf_counter() - t0
        n_tokens = sum(len(v) for v in router.outputs().values())
        stats = router.fleet_stats()
        # prefix accounting aggregated across the replicas' schedulers
        hit_tokens = sum(
            r.scheduler.stats["prefix_hit_tokens"] for r in reps
        )
        fed_tokens = sum(
            r.scheduler.stats["prefill_tokens"] for r in reps
        )
        prompt_tokens = sum(
            len(prefixes[i % len(prefixes)]) + len(tails[i])
            for i in range(rid)
        )
        scaling = router.scaling_signals()
        for rep in reps:
            rep.stop()
        return {
            "scaling": scaling,
            "routed_affine": stats["routed_affine"],
            "routed_fallback": stats["routed_fallback"],
            "affinity_hit_rate": stats["affinity_hit_rate"],
            "prefix_hit_tokens": hit_tokens,
            "prefill_tokens": fed_tokens,
            "prompt_tokens": prompt_tokens,
            "hit_rate": round(hit_tokens / max(1, prompt_tokens), 4),
            "wall_s": round(dt, 3),
            "tokens_per_sec": round(n_tokens / dt, 2) if dt > 0 else 0.0,
            "per_replica_tokens": {
                name: row["tokens_out"]
                for name, row in stats["replicas"].items()
            },
        }

    def cache_compare():
        """radix vs chain on ONE engine under pool pressure: shared
        trunk + cold fillers; the radix tree evicts only the
        shortfall, the chain sweeps everything idle."""
        engine = engines[0]
        trunk = rng.randint(0, vocab, size=2 * bs).tolist()
        tail_len = max(1, bs // 2)
        filler_len = 4 * bs - 4
        phase1 = [trunk + rng.randint(0, vocab, size=tail_len).tolist()
                  for _ in range(2)]
        fillers = [rng.randint(0, vocab, size=filler_len).tolist()
                   for _ in range(2)]
        phase3 = [trunk + rng.randint(0, vocab, size=tail_len).tolist()
                  for _ in range(2)]
        out = {}
        for impl in ("chain", "radix"):
            sched = ContinuousBatchingScheduler(
                engine, pool=engine.make_pool(10), prefix_impl=impl
            )
            rid = 0
            for batch in (phase1, fillers, phase3):
                for p in batch:
                    sched.submit(Request(id=f"c{rid}", prompt=list(p),
                                         max_new_tokens=2))
                    rid += 1
                sched.run()
            prompt_tokens = sum(
                len(p) for p in phase1 + fillers + phase3
            )
            out[impl] = {
                "hit_tokens": sched.stats["prefix_hit_tokens"],
                "prefill_tokens": sched.stats["prefill_tokens"],
                "hit_rate": round(
                    sched.stats["prefix_hit_tokens"] / prompt_tokens, 4
                ),
                "outputs": dict(sched.finished),
            }
        identical = out["chain"]["outputs"] == out["radix"]["outputs"]
        return {
            "radix_hit_rate": out["radix"]["hit_rate"],
            "chain_hit_rate": out["chain"]["hit_rate"],
            "radix_hit_tokens": out["radix"]["hit_tokens"],
            "chain_hit_tokens": out["chain"]["hit_tokens"],
            "radix_prefill_tokens": out["radix"]["prefill_tokens"],
            "chain_prefill_tokens": out["chain"]["prefill_tokens"],
            "outputs_identical": identical,
        }

    def failover():
        n_req = knobs["fleet_failover_requests"]
        f_new = knobs["fleet_failover_new_tokens"]
        prompts = [
            rng.randint(0, vocab,
                        size=int(rng.randint(bs // 2, 2 * bs))).tolist()
            for _ in range(n_req)
        ]

        def run_arm(kill):
            reps, router = build(n=2)
            for j, p in enumerate(prompts):
                router.submit(Request(id=f"k{j}", prompt=list(p),
                                      max_new_tokens=f_new))
            if kill:
                deadline = time.perf_counter() + 600
                while True:
                    by = {}
                    for s in router._streams.values():
                        if not s.done and s.tokens:
                            by[s.replica] = by.get(s.replica, 0) + 1
                    if by and max(by.values()) >= 2:
                        break
                    if time.perf_counter() > deadline:
                        break
                    router.pump()
                    time.sleep(0.002)
                victim = max(by, key=by.get)
                next(r for r in reps if r.name == victim).kill()
            out = router.run(timeout_s=600)
            stats = router.fleet_stats()
            for rep in reps:
                rep.stop()
            return out, stats

        base_out, _ = run_arm(kill=False)
        chaos_out, stats = run_arm(kill=True)
        return {
            "evictions": stats["evictions"],
            "readmissions": stats["readmissions"],
            "token_identical": base_out == chaos_out,
        }

    def shed():
        reps, router = build(n=2)
        red = {"v": False}
        reps[0].set_health_fn(lambda: not red["v"])
        red["v"] = True
        router.pump()
        for j in range(3):
            router.submit(Request(id=f"s{j}", prompt=[j + 1, 2, 3],
                                  max_new_tokens=2))
        router.run(timeout_s=600)
        tokens_while_red = router.fleet_stats()["replicas"]["b0"][
            "tokens_out"
        ]
        red["v"] = False
        router.pump()
        stats = router.fleet_stats()
        for rep in reps:
            rep.stop()
        return {
            "shed_events": stats["shed_events"],
            "tokens_admitted_while_red": tokens_while_red,
            "shed_seconds": stats["replicas"]["b0"]["shed_seconds"],
        }

    warm()
    affine = routing_arm(affinity=True)
    rr = routing_arm(affinity=False)
    scaling = affine.pop("scaling")
    rr.pop("scaling", None)
    detail = {
        "scaling": scaling,
        "replicas": n_replicas,
        "workload": {
            "prefixes": knobs["fleet_prefixes"],
            "requests_per_prefix": knobs["fleet_requests_per_prefix"],
            "prefix_len": knobs["fleet_prefix_len"],
            "tail_len": knobs["fleet_tail"],
            "max_new_tokens": new,
        },
        "affinity": affine,
        "round_robin": rr,
        "affinity_beats_round_robin": (
            affine["prefix_hit_tokens"] > rr["prefix_hit_tokens"]
            and affine["prefill_tokens"] < rr["prefill_tokens"]
        ),
        "cache_compare": cache_compare(),
        "failover": failover(),
        "shed": shed(),
    }
    return detail


def _publish_probe(model, knobs):
    """detail.publish: the online-learning live swap measured from the
    SERVING side (docs/online_learning.md) — an in-process EASGD core
    publishes a new center mid-decode, the replica's subscriber pulls,
    validates, and installs between ticks.  This probe records the
    swap's serving-visible COSTS (install wait behind in-flight work,
    snapshot bytes pulled, extra compiles); full correctness — token
    identity, rollback, refusal — is the PUBLISH chaos drill's job
    (runtime/chaos.py, perf_gate publish leg)."""
    import numpy as np

    from theanompi_tpu.parallel.distributed_async import EasgdServerCore
    from theanompi_tpu.publish import WeightSubscriber
    from theanompi_tpu.serving import PagedServingEngine, Request
    from theanompi_tpu.serving.fleet import FleetRouter, ServeReplica
    from theanompi_tpu.serving.loader import relayout_for_serving

    bs = knobs["block_size"]
    engine = PagedServingEngine(
        model, n_slots=knobs["paged_slots"], max_len=knobs["max_len"],
        block_size=bs, prefill_chunk=knobs["prefill_chunk"],
    )
    rep = ServeReplica("pub0", engine).start()
    router = FleetRouter(evict_after_s=3600.0)
    router.add_replica("pub0", rep)

    params0 = jax.tree.map(np.array, jax.device_get(model.params))
    snapshot_bytes = sum(
        a.nbytes for a in jax.tree.leaves(params0)
        if hasattr(a, "nbytes")
    )
    publish_every = 2
    core = EasgdServerCore(
        jax.tree.map(np.copy, params0), alpha=0.5,
        publish_every=publish_every,
    )
    rng = np.random.RandomState(_SEED_BASE + 7)
    worker = jax.tree.map(
        lambda a: a + rng.normal(0, 0.02, a.shape).astype(a.dtype)
        if a.dtype == np.float32 else a,
        params0,
    )
    core.handler({"kind": "join", "rank": 0})

    def fetch(generation):
        reply = core.handler(
            {"kind": "weights", "generation": int(generation)}
        )
        return reply if reply.get("ok") else None

    sub = WeightSubscriber(
        rep, fetch, relayout=lambda p: relayout_for_serving(model, p)
    )

    # one prompt length -> one prefill bucket: the probe's trace pin
    # isolates the SWAP's compile cost, not workload bucket variety
    n_req = 4
    new = min(8, knobs["max_new_tokens"])
    prompts = [
        rng.randint(0, knobs["vocab_size"], size=bs + 2).tolist()
        for _ in range(n_req)
    ]

    def cohort(tag):
        ids = []
        for j, p in enumerate(prompts):
            r = Request(id=f"{tag}{j}", prompt=list(p),
                        max_new_tokens=new)
            router.submit(r)
            ids.append(r.id)
        out = router.run(timeout_s=600)
        return [list(out[i]) for i in ids]

    try:
        cohort("warm")  # compile both phases outside every measurement
        traces0 = (engine._n_prefill_traces, engine._n_decode_traces)

        # cohort A decoding when the publish lands: install must wait
        # for the in-flight work (the between-ticks/idle contract)
        for j, p in enumerate(prompts):
            router.submit(Request(id=f"a{j}", prompt=list(p),
                                  max_new_tokens=new))
        deadline = time.perf_counter() + 600
        while not any(
            s.tokens and not s.done for s in router._streams.values()
        ):
            if time.perf_counter() > deadline:
                raise RuntimeError("publish probe never started decoding")
            router.pump()
            time.sleep(0.002)
        ann = None
        for _ in range(publish_every):
            ann = core.handler(
                {"kind": "exchange", "rank": 0,
                 "params": jax.tree.map(np.copy, worker)}
            ).get("publish", ann)
        t_pub = time.perf_counter()
        accepted = sub.poll(ann)
        deferred = rep.serving_generation == 0
        while rep.serving_generation != 1:
            if time.perf_counter() > deadline:
                raise RuntimeError("publish probe install never landed")
            router.pump()
            time.sleep(0.002)
        install_wait = time.perf_counter() - t_pub
        a_out = [list(router.run(timeout_s=600)[f"a{j}"])
                 for j in range(n_req)]

        b_out = cohort("b")  # admitted on the new generation
        traces1 = (engine._n_prefill_traces, engine._n_decode_traces)
        return {
            "publish_every": publish_every,
            "published": core.publisher.n_published,
            "announced_generation": (
                int(ann["generation"]) if ann else 0
            ),
            "accepted": bool(accepted),
            "snapshot_bytes": int(snapshot_bytes),
            "install_deferred_while_busy": bool(deferred),
            "install_wait_s": round(install_wait, 4),
            "serving_generation": rep.serving_generation,
            "installs": sub.installs,
            "refusals": sub.refusals,
            # different weights should decode differently; recorded,
            # not asserted (the drill owns correctness claims)
            "outputs_changed_across_swap": a_out != b_out,
            "extra_prefill_traces": traces1[0] - traces0[0],
            "extra_decode_traces": traces1[1] - traces0[1],
        }
    finally:
        rep.stop()


def _request_forensics(knobs):
    """detail.request_forensics: the request doctor's verdict on the
    measured open-loop window — phase breakdown of the slowest request
    (worst-latency ring: present even when nothing breached the
    retention threshold) plus the retain/recycle accounting the gate
    reads.  Pure host-side bookkeeping; never touches the engine."""
    from theanompi_tpu import observability
    from theanompi_tpu.observability import analysis as obs_analysis

    stats = observability.request_stats()
    out = {
        "threshold_s": knobs["forensics_threshold_s"],
        "tracked": stats["tracked"],
        "retained": stats["retained"],
        "recycled": stats["recycled"],
        "retained_rids": sorted(
            r["rid"] for r in observability.retained_requests()
        ),
    }
    worst = observability.worst_requests()
    if worst:
        slowest = obs_analysis.request_breakdown(worst[0])
        out["slowest"] = slowest
        out["coverage"] = slowest["coverage"]
    return out


def _long_tail_prompts(rng, knobs):
    """Mixed-length burst: mostly short prompts, a long tail near
    max_len — the workload shape that wastes contiguous slot memory."""
    lo, n = knobs["prompt_lo"], knobs["long_tail_requests"]
    new = knobs["long_tail_new_tokens"]
    long_len = knobs["max_len"] - new  # as long as a lane can hold
    short_hi = max(lo + 1, knobs["prompt_hi"] // 2)
    out = []
    for j in range(n):
        if rng.rand() < knobs["long_tail_frac_long"]:
            size = long_len
        else:
            size = rng.randint(lo, short_hi + 1)
        out.append(rng.randint(0, knobs["vocab_size"], size=size).tolist())
    return out


def main(argv=None):
    import argparse

    import numpy as np

    ap = argparse.ArgumentParser(prog="bench_serve.py")
    ap.add_argument(
        "--replicas", type=int,
        default=int(os.environ.get("THEANOMPI_BENCH_SERVE_REPLICAS", "0")),
        help="serving-fleet probe size (0 = knob default; the probe "
        "runs whenever the paged engine does)",
    )
    args = ap.parse_args(argv)

    knobs = dict(_KNOBS_REHEARSAL if CPU_REHEARSAL else _KNOBS_REAL)
    # candidate-config injection for the self-tuning driver: named
    # workload/geometry knobs (spec_k, prefill_chunk, fleet_replicas,
    # ...) override the knob table; kv_dtype re-types the headline
    # engine's KV pool; trace_sample rides into enable_tracing
    tune = _tune_overrides()
    tune_kv_dtype = "fp32"
    tune_sample = None
    if tune is not None:
        for t_name, t_value in sorted(tune.items()):
            if t_name == "kv_dtype":
                tune_kv_dtype = str(t_value)
            elif t_name == "trace_sample":
                tune_sample = int(t_value)
            elif t_name in knobs:
                knobs[t_name] = type(knobs[t_name])(t_value)
            else:
                print(f"[bench_serve] unknown tune override {t_name!r}",
                      file=sys.stderr)
                sys.exit(2)
    n_fleet = args.replicas or knobs["fleet_replicas"]
    # same attribution contract as bench.py: the BENCH_serve line
    # carries trace-export paths + a metrics snapshot (TTFT/TPOT
    # histograms, slot/queue gauges, prefill-bucket counters,
    # block-pool occupancy, prefix hit counters)
    from theanompi_tpu import observability as observability
    from theanompi_tpu.observability import live as obs_live

    observability.enable_tracing(sample=tune_sample)
    # live plane (THEANOMPI_LIVE=1): the persisted verdict timeline is
    # what the tuning driver's history-diff gate compares round-over-
    # round (trials.py sets THEANOMPI_LIVE_PERSIST per trial)
    telemetry = obs_live.maybe_start_from_env("serve0")
    if not CPU_REHEARSAL and jax.default_backend() not in ("tpu",):
        # same guard shape as bench.py: a dead tunnel silently falling
        # back to 1 CPU device must not masquerade as a TPU number
        emit(0.0, {"error": f"backend is {jax.default_backend()!r}, not "
                   "tpu — set THEANOMPI_BENCH_CPU=1 for the rehearsal"},
             measured_now=False)
        sys.exit(1)

    from theanompi_tpu.models.transformer import TransformerLM
    from theanompi_tpu.runtime.recorder import Recorder
    from theanompi_tpu.serving import (
        ContinuousBatchingScheduler, PagedServingEngine, Request,
        ServingEngine, ServingMetrics,
    )

    cfg = dict(
        seq_len=knobs["seq_len"], vocab_size=knobs["vocab_size"],
        d_model=knobs["d_model"], n_heads=knobs["n_heads"],
        n_layers=knobs["n_layers"], batch_size=1, n_synth_train=2,
        n_synth_val=1, comm_probe=False, print_freq=10_000,
    )
    model = TransformerLM(config=cfg)
    engine_kind = (
        os.environ.get("THEANOMPI_BENCH_SERVE_ENGINE") or "paged"
    ).lower()
    # contiguous reference: n_slots worst-case regions = the equal-
    # memory budget every comparison below is pinned to
    contiguous_blocks = knobs["n_slots"] * (
        knobs["max_len"] // knobs["block_size"]
    )
    if engine_kind == "contiguous":
        engine = ServingEngine(
            model, n_slots=knobs["n_slots"], max_len=knobs["max_len"]
        )
    else:
        engine = PagedServingEngine(
            model, n_slots=knobs["paged_slots"], max_len=knobs["max_len"],
            block_size=knobs["block_size"],
            n_blocks=contiguous_blocks + 1,  # +1: reserved trash block
            prefill_chunk=knobs["prefill_chunk"],
            kv_dtype=tune_kv_dtype,
        )
    rec = Recorder(verbose=False)
    metrics = ServingMetrics(recorder=rec)
    sched = ContinuousBatchingScheduler(engine, metrics=metrics)

    # seeded open-loop Poisson workload, pre-drawn
    rng = np.random.RandomState(_SEED_BASE + 0)
    n = knobs["n_requests"]
    arrivals = np.cumsum(rng.exponential(
        1.0 / knobs["arrival_rate_rps"], size=n
    ))
    prompts = [
        rng.randint(0, knobs["vocab_size"],
                    size=rng.randint(knobs["prompt_lo"],
                                     knobs["prompt_hi"] + 1)).tolist()
        for _ in range(n)
    ]

    # warm the compiles OUTSIDE the measured window (one prefill bucket
    # per distinct bucket + the decode step), mirroring bench.py's
    # warmup-exclusion protocol
    warm = ContinuousBatchingScheduler(engine, metrics=None)
    warm.submit(Request(id="warm", prompt=prompts[0],
                        max_new_tokens=min(2, knobs["max_new_tokens"])))
    warm.run()

    # request forensics cover EXACTLY the measured window: enabled
    # after warmup (a tracked warm request's compile time would
    # masquerade as the slowest request) and disabled before the
    # capacity probes (the failover probe kills a replica on purpose —
    # its flagged retentions must not read as a red headline run)
    observability.enable_request_tracking(
        threshold_s=knobs["forensics_threshold_s"]
    )
    dt = _drive_open_loop(sched, Request, prompts, arrivals,
                          knobs["max_new_tokens"])
    forensics_detail = _request_forensics(knobs)
    observability.disable_request_tracking()

    # ---- paged capacity probes (CPU bench acceptance evidence) -------
    paged_detail = None
    if engine_kind != "contiguous":
        wl_rng = np.random.RandomState(_SEED_BASE + 1)
        lt_prompts = _long_tail_prompts(wl_rng, knobs)
        # paged at EQUAL cache memory: the accounted pool is capped to
        # exactly the contiguous engine's row budget
        lt_paged = ContinuousBatchingScheduler(
            engine, pool=engine.make_pool(contiguous_blocks + 1)
        )
        _drive_burst(lt_paged, Request, lt_prompts,
                     knobs["long_tail_new_tokens"], "lt")
        # the contiguous engine on the SAME burst (its peak concurrency
        # is structurally capped at n_slots — measured, not assumed)
        eng_c = ServingEngine(
            model, n_slots=knobs["n_slots"], max_len=knobs["max_len"]
        )
        lt_contig = ContinuousBatchingScheduler(eng_c)
        _drive_burst(lt_contig, Request, lt_prompts,
                     knobs["long_tail_new_tokens"], "lt")
        ratio = (
            lt_paged.stats["peak_concurrent"]
            / max(1, lt_contig.stats["peak_concurrent"])
        )

        # shared-system-prompt workload: one distinct prefix, many
        # tails; reuse ON vs OFF over the same requests
        sys_prompt = wl_rng.randint(
            0, knobs["vocab_size"], size=knobs["prefix_len"]
        ).tolist()
        pf_prompts = [
            sys_prompt + wl_rng.randint(
                0, knobs["vocab_size"], size=knobs["prefix_tail"]
            ).tolist()
            for _ in range(knobs["prefix_requests"])
        ]
        pf_sched = ContinuousBatchingScheduler(engine)
        for j, p in enumerate(pf_prompts):
            pf_sched.submit(Request(id=f"pf{j}", prompt=list(p),
                                    max_new_tokens=knobs["prefix_new_tokens"]))
            pf_sched.step()  # arrivals spaced a tick apart: reuse is
            # only possible once the first prefix is resident
        pf_out = pf_sched.run()
        no_reuse = ContinuousBatchingScheduler(
            engine, pool=engine.make_pool()
        )
        no_reuse.prefix = None  # same engine, reuse disabled
        for j, p in enumerate(pf_prompts):
            no_reuse.submit(Request(id=f"pf{j}", prompt=list(p),
                                    max_new_tokens=knobs["prefix_new_tokens"]))
            no_reuse.step()
        nr_out = no_reuse.run()
        if pf_out != nr_out:  # reuse must never change results
            emit(0.0, {"error": "prefix reuse changed outputs"},
                 measured_now=False)
            sys.exit(1)
        total_prompt_tokens = sum(len(p) for p in pf_prompts)
        paged_detail = {
            "block_size": knobs["block_size"],
            "pool_blocks": contiguous_blocks,
            "prefill_chunk": knobs["prefill_chunk"],
            "paged_slots": knobs["paged_slots"],
            "long_tail": {
                "n_requests": knobs["long_tail_requests"],
                "frac_long": knobs["long_tail_frac_long"],
                "equal_memory_rows": contiguous_blocks
                * knobs["block_size"],
                "contiguous_slots": knobs["n_slots"],
                "contiguous_peak_concurrent":
                    lt_contig.stats["peak_concurrent"],
                "paged_peak_concurrent":
                    lt_paged.stats["peak_concurrent"],
                "concurrency_ratio": round(ratio, 3),
                "paged_backpressure_events":
                    lt_paged.stats["backpressure_events"],
                "paged_pool_peak_used_blocks": lt_paged.pool.peak_used,
            },
            "prefix": {
                "n_requests": knobs["prefix_requests"],
                "shared_prefix_len": knobs["prefix_len"],
                "hits": pf_sched.stats["prefix_hits"],
                "hit_tokens": pf_sched.stats["prefix_hit_tokens"],
                "hit_rate": round(
                    pf_sched.stats["prefix_hit_tokens"]
                    / total_prompt_tokens, 4
                ),
                "prefill_tokens": pf_sched.stats["prefill_tokens"],
                "prefill_tokens_no_reuse":
                    no_reuse.stats["prefill_tokens"],
            },
        }

    # ---- decode-speed probes (ISSUE 11) -----------------------------
    spec_detail = None
    kv_quant_detail = None
    if engine_kind != "contiguous":
        kv_quant_detail = _kv_quant_probe(model, engine, knobs, prompts)
        spec_detail = _spec_probe(knobs)

    # ---- serving-fleet probe (ISSUE 12) -----------------------------
    fleet_detail = None
    if engine_kind != "contiguous" and n_fleet >= 2:
        fleet_detail = _fleet_probe(model, knobs, n_fleet)

    # ---- online-learning publish probe (ISSUE 18) -------------------
    publish_detail = None
    if engine_kind != "contiguous":
        publish_detail = _publish_probe(model, knobs)

    summary = metrics.summary()
    n_tokens = summary["n_tokens_out"]
    detail = {
        "chips": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind,
        "engine": engine_kind,
        "model": {k: knobs[k] for k in
                  ("d_model", "n_heads", "n_layers", "vocab_size")},
        "n_slots": engine.n_slots,
        "max_len": knobs["max_len"],
        "buckets": list(engine.buckets),
        "workload": {
            "n_requests": n,
            "arrival_rate_rps": knobs["arrival_rate_rps"],
            "prompt_len_range": [knobs["prompt_lo"], knobs["prompt_hi"]],
            "max_new_tokens": knobs["max_new_tokens"],
            "distribution": "poisson(open-loop), seeded",
        },
        "wall_s": round(dt, 3),
        "ttft_p50_s": round(summary["ttft_p50_s"], 4),
        "ttft_p99_s": round(summary["ttft_p99_s"], 4),
        "tpot_p50_s": round(summary["tpot_p50_s"], 4),
        "tpot_p99_s": round(summary["tpot_p99_s"], 4),
        # which estimator produced each percentile pair: "exact"
        # nearest-rank over the per-request rows, or "histogram"
        # bucket interpolation once the row window overflowed — a
        # JSON consumer must never mistake one for the other
        "percentile_estimators": summary["estimators"],
        "cpu_rehearsal": CPU_REHEARSAL,
    }
    if "engine_stats" in summary:
        detail["engine_stats"] = summary["engine_stats"]
    detail["request_forensics"] = forensics_detail
    if paged_detail is not None:
        detail["paged"] = paged_detail
    if spec_detail is not None:
        detail["spec"] = spec_detail
    if kv_quant_detail is not None:
        detail["kv_quant"] = kv_quant_detail
    if fleet_detail is not None:
        detail["fleet"] = fleet_detail
    if publish_detail is not None:
        detail["publish"] = publish_detail
    if tune is not None:
        # echo the candidate config: the trial harness proves injection
        # by comparing this against what it sent
        detail["tuning"] = {
            "overrides": tune,
            "seed": TUNE_SEED,
            "budget": os.environ.get("THEANOMPI_TUNE_BUDGET", "full"),
        }
    live_summary = None
    if telemetry is not None:
        try:
            live_summary = telemetry.stop()
        except Exception as e:  # the monitor must never cost the number
            live_summary = f"failed: {type(e).__name__}: {e}"
    try:
        paths = observability.dump_all(prefix="bench_serve_")
        detail["observability"] = {
            "trace_chrome": paths["trace_chrome"],
            "trace_raw": paths["trace_raw"],
            "metrics_json": paths["metrics_json"],
            "metrics": observability.get_registry().snapshot(),
        }
        if live_summary is not None:
            detail["observability"]["live"] = live_summary
        if "doctor" in paths:
            detail["observability"]["doctor"] = paths["doctor"]
    except OSError as e:  # export must never discard the measurement
        print(f"[bench_serve] observability export failed: {e}",
              file=sys.stderr, flush=True)
        detail["observability"] = f"failed: {type(e).__name__}: {e}"
    emit(n_tokens / dt, detail, measured_now=True)


if __name__ == "__main__":
    main()
